"""Dynamic request batcher: bounded admission queue + coalescing
scheduler.

Clipper-style adaptive batching over bucketed static shapes: client
threads ``submit()`` single examples into a bounded queue; one
scheduler thread coalesces whatever arrived inside the batching window
(``max_wait_us``) — or as soon as ``max_batch`` requests are waiting —
into one padded dispatch through the :class:`ModelRunner`. Padding to a
pre-warmed bucket keeps the compiled-graph cache key stable, so after
warmup the XLA compile counter stays flat no matter how request sizes
mix (``recompiles`` in :meth:`stats` machine-checks it).

Admission control:

* queue at ``queue_depth`` → the request is shed at submit with
  :class:`ServerOverloaded` (clients back off; the queue never grows
  without bound);
* a request whose deadline expires while queued is aborted with
  :class:`DeadlineExceeded` BEFORE any device dispatch — expiry is
  checked when the batch is cut, so a stalled scheduler never burns
  device time on answers nobody is waiting for;
* ``close(drain=True)`` stops admission and flushes the queue;
  ``close(drain=False)`` rejects everything still queued with
  :class:`ServerClosed`.

Locking (declared in ``analysis/locks.py``): ``_cv`` is the single
``serve.queue`` condition — OUTERMOST in the hierarchy because the
scheduler releases it before touching the model; no lock is ever held
across a dispatch. Tests drive :meth:`run_once` directly with a fake
``clock`` for fully deterministic coalescing/expiry scenarios — the
scheduler thread runs the exact same code path.
"""

import threading
import time
from collections import deque
from concurrent.futures import Future

from ..analysis import race as _race
from ..telemetry import trace as _trace
from . import faults as _faults
from .errors import DeadlineExceeded, ServerClosed, ServerOverloaded
from .metrics import ServingMetrics, register as _register, \
    unregister as _unregister

__all__ = ['DynamicBatcher', 'Request']

_DEF_QUEUE_DEPTH = 256
_DEF_MAX_WAIT_US = 2000


def _env_int(name, default):
    import os
    v = os.environ.get(name, '')
    return int(v) if v.strip() else default


def _env_float(name, default):
    import os
    v = os.environ.get(name, '')
    return float(v) if v.strip() else default


class Request:
    """One queued example: payload + completion future + timing."""

    __slots__ = ('payload', 'future', 'submit_t', 'deadline', 'tc',
                 'wall_t')

    def __init__(self, payload, submit_t, deadline):
        self.payload = payload
        self.future = Future()
        self.submit_t = submit_t
        self.deadline = deadline        # absolute clock time or None
        # trace context captured at submission; None (the untraced
        # common case) short-circuits the scheduler's telemetry path
        self.tc = _trace.current_tc()
        self.wall_t = _trace.walltime() if self.tc is not None else 0.0


class DynamicBatcher:
    """Coalesce single-example submissions into bucketed batches.

    Parameters
    ----------
    runner : ModelRunner
        The registered (linted + pre-warmed) model.
    max_batch : int, optional
        Cap on rows per dispatch (default: the runner's largest
        bucket; larger queues are split across dispatches).
    max_wait_us : int, optional
        Batching window in microseconds (``MXNET_SERVE_MAX_WAIT_US``,
        default 2000): how long the first queued request waits for
        company before the batch is cut.
    queue_depth : int, optional
        Admission bound (``MXNET_SERVE_QUEUE_DEPTH``, default 256).
    deadline_ms : float, optional
        Default per-request deadline (``MXNET_SERVE_DEADLINE_MS``,
        unset = no deadline); ``submit(deadline_ms=...)`` overrides.
    clock : callable
        Monotonic time source (tests inject a fake clock).
    start : bool
        Start the scheduler thread (False for deterministic tests that
        call :meth:`run_once` themselves).
    """

    def __init__(self, runner, max_batch=None, max_wait_us=None,
                 queue_depth=None, deadline_ms=None,
                 clock=time.monotonic, name=None, start=True):
        self.runner = runner
        self.max_batch = min(max_batch or runner.max_batch,
                             runner.max_batch)
        if max_wait_us is None:
            max_wait_us = _env_int('MXNET_SERVE_MAX_WAIT_US',
                                   _DEF_MAX_WAIT_US)
        self.max_wait = max_wait_us / 1e6
        self.queue_depth = queue_depth if queue_depth is not None \
            else _env_int('MXNET_SERVE_QUEUE_DEPTH', _DEF_QUEUE_DEPTH)
        if deadline_ms is None:
            deadline_ms = _env_float('MXNET_SERVE_DEADLINE_MS', 0.0)
        self.default_deadline = (deadline_ms / 1e3) or None
        self._clock = clock
        self.name = name or f'batcher:{runner.name}'

        # serve.queue — outermost: released before every model dispatch
        self._cv = _race.tracked_condition(threading.Condition(),
                                           'serve.queue')
        self._queue = deque()
        self._queue_state = _race.shared_state(
            f'{self.name}._queue', guard='serve.queue')
        self._draining = False
        self._closed = False

        self.metrics = ServingMetrics(self.name)
        self._metrics_name = _register(self.name, self.metrics)
        self.compile_baseline = runner.compile_count

        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._scheduler_loop, daemon=True,
                name=f'{self.name}-sched')
            self._thread.start()

    # --------------------------------------------------------- admission
    def submit(self, payload, deadline_ms=None):
        """Enqueue one example; returns a Future resolving to its
        (already unpadded) output row. Sheds with ServerOverloaded at
        capacity, ServerClosed once draining/closed."""
        now = self._clock()
        if deadline_ms is None:
            dl = now + self.default_deadline if self.default_deadline \
                else None
        else:
            dl = now + deadline_ms / 1e3
        req = Request(payload, now, dl)
        with self._cv:
            if self._closed or self._draining:
                raise ServerClosed(f'{self.name} is not accepting work')
            if len(self._queue) >= self.queue_depth:
                self.metrics.on_shed()
                raise ServerOverloaded(
                    f'{self.name} queue at capacity '
                    f'({self.queue_depth}); request shed')
            self._queue_state.write()
            self._queue.append(req)
            self.metrics.on_submit()
            self._cv.notify()
        return req.future

    def submit_sync(self, payload, deadline_ms=None, timeout=None):
        """submit() + block for the result."""
        return self.submit(payload, deadline_ms).result(timeout)

    # --------------------------------------------------------- scheduling
    @_race.guarded_by('_cv')
    def _cut_batch(self, now):
        """Pop one dispatchable batch, expiring dead requests first.
        Returns (batch, expired) — called with the queue lock held."""
        expired = []
        while self._queue and self._queue[0].deadline is not None \
                and self._queue[0].deadline <= now:
            self._queue_state.write()
            expired.append(self._queue.popleft())
        batch = []
        while self._queue and len(batch) < self.max_batch:
            req = self._queue[0]
            if req.deadline is not None and req.deadline <= now:
                self._queue_state.write()
                expired.append(self._queue.popleft())
                continue
            self._queue_state.write()
            batch.append(self._queue.popleft())
        return batch, expired

    def run_once(self, block=True, timeout=0.1):
        """One scheduler iteration: honor the batching window, cut a
        batch, dispatch it. Returns the number of requests resolved
        (completed + expired); 0 when idle or (non-blocking) while the
        window is still open.

        ``block=False`` never sleeps — tests drive this directly with a
        fake clock for deterministic coalescing and expiry scenarios.
        """
        with self._cv:
            if block:
                self._cv.wait_for(
                    lambda: self._queue or self._closed, timeout)
            if not self._queue:
                return 0
            # batching window: the OLDEST request waits at most
            # max_wait for company; full batch or drain cuts it early
            while (len(self._queue) < self.max_batch
                    and not self._draining and not self._closed):
                remaining = (self._queue[0].submit_t + self.max_wait
                             - self._clock())
                if remaining <= 0:
                    break
                if not block:
                    return 0            # window open: nothing to do yet
                self._cv.wait(remaining)
                if not self._queue:
                    return 0
            batch, expired = self._cut_batch(self._clock())
        # ---- lock released: everything below may block on the device
        for req in expired:
            self.metrics.on_expired()
            self._fail(req, DeadlineExceeded(
                'deadline expired in queue; aborted before dispatch'))
        if not batch:
            return len(expired)
        traced = [r for r in batch if r.tc is not None]
        t0w = _trace.walltime() if traced else 0.0
        try:
            _faults.on('dispatch')
            rows, n_pad = self.runner.run_batch(
                [r.payload for r in batch])
        except Exception as e:               # noqa: BLE001 — fail the batch
            for req in batch:
                self.metrics.on_failed()
                self._fail(req, e)
            return len(batch) + len(expired)
        now = self._clock()
        if traced:
            t1w = _trace.walltime()
            for req in traced:
                # retroactive spans per traced request: its queue wait
                # (submit -> batch cut) and its ride on the dispatch
                _trace.emit('batch.queue', req.wall_t, t0w,
                            parent=req.tc, batcher=self.name)
                _trace.emit('batch.dispatch', t0w, t1w, parent=req.tc,
                            batcher=self.name, rows=len(batch),
                            pad=n_pad)
        self.metrics.on_dispatch(
            len(batch), n_pad, [now - r.submit_t for r in batch])
        if self.runner.compile_count != self.compile_baseline:
            self.metrics.on_recompile(
                self.runner.compile_count - self.compile_baseline)
            self.compile_baseline = self.runner.compile_count
        for req, row in zip(batch, rows):
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(row)
            self.metrics.on_complete(self._clock() - req.submit_t)
        return len(batch) + len(expired)

    @staticmethod
    def _fail(req, exc):
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(exc)

    def _scheduler_loop(self):
        while True:
            self.run_once(block=True)
            with self._cv:
                if self._closed and not self._queue:
                    return
                if self._draining and not self._queue:
                    self._closed = True
                    self._cv.notify_all()
                    return

    # ------------------------------------------------------------- close
    def close(self, drain=True, timeout=10.0):
        """Stop admission. ``drain=True`` flushes queued work first;
        ``drain=False`` rejects it with ServerClosed immediately."""
        with self._cv:
            if self._closed:
                return
            self._draining = True
            if not drain:
                while self._queue:
                    self._queue_state.write()
                    req = self._queue.popleft()
                    self._fail(req, ServerClosed(
                        f'{self.name} closed without drain'))
                self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        else:
            # deterministic mode: the caller owns the loop — flush here
            while drain and self.run_once(block=False):
                pass
            with self._cv:
                self._closed = True
        _unregister(self._metrics_name)

    @property
    def closed(self):
        with self._cv:
            return self._closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=exc[0] is None)
        return False

    # ------------------------------------------------------------- stats
    def stats(self):
        """Metrics snapshot plus the zero-recompile check's inputs."""
        out = self.metrics.snapshot()
        out['compile_count'] = self.runner.compile_count
        with self._cv:
            out['queued'] = len(self._queue)
        return out

    def __repr__(self):
        return (f'<DynamicBatcher {self.name!r} max_batch={self.max_batch} '
                f'window={self.max_wait * 1e6:.0f}us '
                f'depth={self.queue_depth}>')
