"""Custom operators written in Python.

Reference: ``python/mxnet/operator.py`` (CustomOp:434, CustomOpProp:487,
register:710) backed by ``src/operator/custom/custom-inl.h:52-136`` — user
ops run on a dedicated async worker so arbitrary Python can't stall the
engine.

TPU re-design: like the reference, the user's ``forward`` runs on a
DEDICATED worker thread (custom-inl.h:52 ``CustomOperator`` keeps its
own task queue precisely so arbitrary Python cannot stall the engine):
``custom()`` enqueues the op and immediately returns *pending* NDArrays
(shape/dtype from ``infer_shape``/``infer_type``); touching a result is
a sync point that waits for the worker and re-raises any exception the
user code threw — the engine's exception-at-sync-point contract
(threaded_engine.h:365). Ops execute in push order (FIFO, one worker,
matching the reference's per-op serial queue). Autograd wires
``backward`` in as a custom VJP on the tape — the same mechanism as
``autograd.Function``. If the op body calls host code (numpy etc.) it
stays an eager-only island, matching the reference where custom ops
break graph fusion.
"""

import threading as _threading

import numpy as _np

from . import _tape
from .ndarray.ndarray import NDArray

_REGISTRY = {}


class _Worker:
    """The dedicated custom-op worker thread (reference
    CustomOperator::GetSharedRef()->Push, custom-inl.h:52-136)."""

    _instance = None
    _lock = _threading.Lock()

    def __init__(self):
        import queue
        self._q = queue.Queue()
        self._t = _threading.Thread(target=self._run, daemon=True,
                                    name='mxnet-custom-op-worker')
        self._t.start()

    @classmethod
    def get(cls):
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def _run(self):
        while True:
            task = self._q.get()
            task()                  # task handles its own exceptions

    def push(self, task):
        self._q.put(task)


class _PendingCustom:
    """Duck-typed 'segment' for :class:`_bulk.LazyRef`: materializing a
    custom op's output waits for the worker task and re-raises the user
    exception at the sync point."""

    def __init__(self, op_type):
        self._done = _threading.Event()
        self._exc = None
        self._op_type = op_type
        self.refs = []

    def flush(self):
        self._done.wait()
        if self._exc is not None:
            raise RuntimeError(
                f'custom op {self._op_type!r} failed on the worker '
                f'thread (reference: exception routed to the waiting '
                f'sync point)') from self._exc

    def complete(self, values, exc=None):
        if exc is None:
            for ref, v in zip(self.refs, values):
                ref.value = v
        self._exc = exc
        self._done.set()


class CustomOp:
    """Base class for user ops (reference operator.py:434)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad request (reference
        kWriteTo/kAddTo semantics)."""
        if req == 'null':
            return
        if not isinstance(src, NDArray):
            src = NDArray(src)
        if req == 'add':
            dst._rebind((dst + src)._data)
        else:  # write / inplace
            dst._rebind(src._data)


class CustomOpProp:
    """Op metadata provider (reference operator.py:487)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ['data']

    def list_outputs(self):
        return ['output']

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Register a CustomOpProp subclass (reference operator.py:710)."""

    def deco(prop_cls):
        # lock-lint: disable=unguarded-shared-state -- registration is import-time/main-thread; the worker thread only drains its queue and never touches _REGISTRY
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return deco


def get_prop(name):
    return _REGISTRY[name]


def custom(*args, op_type=None, **kwargs):
    """Invoke a registered custom op: ``mx.nd.Custom(x, op_type='name')``
    (reference: the generated `Custom` op calling CustomOperator::Push).
    """
    if op_type is None:
        raise ValueError('op_type= is required')
    prop = _REGISTRY[op_type](**{k: str(v) for k, v in kwargs.items()})

    in_data = [a if isinstance(a, NDArray) else NDArray(a) for a in args]
    in_shapes = [list(a.shape) for a in in_data]
    _, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    in_types = [a.dtype for a in in_data]
    _, out_types, aux_types = prop.infer_type(in_types)

    from .context import current_context
    ctx = current_context()
    op = prop.create_operator(ctx, in_shapes, [str(t) for t in in_types])

    import jax
    import jax.numpy as jnp
    out_data = [NDArray(jnp.zeros(tuple(s), dtype=_np.dtype(t)))
                for s, t in zip(out_shapes, out_types)]
    aux = [NDArray(jnp.zeros(tuple(s), dtype=_np.dtype(t)))
           for s, t in zip(aux_shapes, aux_types)]

    recording = _tape.is_recording() and _tape._needs_grad(in_data)
    is_train = recording and _tape.is_training()

    # Async dispatch (reference CustomOperator::Push): the user forward
    # runs on the dedicated worker; the caller gets pending NDArrays
    # whose materialization is the sync point.
    from . import _bulk
    out_avals = [jax.ShapeDtypeStruct(tuple(s), _np.dtype(t))
                 for s, t in zip(out_shapes, out_types)]
    pend = _PendingCustom(op_type)
    results = []
    for aval in out_avals:
        ref = _bulk.LazyRef(pend, None, aval)
        nd = NDArray(None, ctx=ctx)
        nd._lazy = ref
        pend.refs.append(ref)
        results.append(nd)

    # Read-dependencies at dispatch time (reference engine read-deps on
    # the pushed op): CONCRETE inputs are snapshotted by value NOW, so
    # an in-place write (x[:] = 0, a trainer step rebinding a weight)
    # after custom() returns cannot race the worker's read. PENDING
    # inputs (another custom op's output, a bulked segment value) are
    # snapshotted by their LazyRef — resolving them is deferred to the
    # worker so chained custom() calls never block the dispatch thread;
    # FIFO guarantees an earlier custom op's value is already set, and
    # a bulk segment flush is thread-safe.
    snaps = []
    for x in in_data:
        ref = x._lazy
        cx = getattr(x, '_ctx', None)
        if ref is not None and ref.value is None:
            snaps.append((ref, None, cx))
        else:
            snaps.append((None, x._data, cx))

    def _task():
        try:
            work_in = []
            for ref, raw, cx in snaps:
                if ref is not None:
                    if ref.value is None and ref.seg is not None:
                        ref.seg.flush()
                    raw = ref.value
                work_in.append(NDArray(raw, ctx=cx))
            # the worker thread's own tape state is thread-local and
            # off by default — user forward code never re-records
            op.forward(is_train=is_train, req=['write'] * len(out_data),
                       in_data=work_in, out_data=out_data, aux=aux)
            pend.complete([o._data for o in out_data])
        except Exception as e:      # route to the caller's sync point
            pend.complete(None, exc=e)

    _Worker.get().push(_task)

    if recording:
        def _fn(*raws):
            return tuple(o._data for o in out_data)

        node = _tape.TapeNode(
            _fn, [x._data for x in in_data],
            [getattr(x, '_ag', None) for x in in_data],
            len(out_data), f'Custom[{op_type}]',
            out_avals=out_avals,
            multi=len(out_data) > 1)

        def _custom_vjp(cots):
            if not isinstance(cots, (tuple, list)):
                cots = (cots,)
            pend.flush()            # backward needs the forward's outputs
            work_in = [NDArray(ref.value if ref is not None else raw,
                               ctx=cx) for ref, raw, cx in snaps]
            in_grad = [NDArray(jnp.zeros(a.shape, dtype=a.dtype))
                       for a in in_data]
            prev = _tape.set_recording(False)
            try:
                op.backward(req=['write'] * len(in_grad),
                            out_grad=[NDArray(c) for c in cots],
                            in_data=work_in, out_data=out_data,
                            in_grad=in_grad, aux=aux)
            finally:
                _tape.set_recording(prev)
            return tuple(g._data for g in in_grad)

        node.vjp_fn = _custom_vjp
        for i, o in enumerate(results):
            o._ag = _tape.AGInfo(node=node, index=i)

    return results[0] if len(results) == 1 else tuple(results)


Custom = custom
