"""``mx.runtime`` — feature introspection.

Reference: ``python/mxnet/runtime.py`` backed by ``src/libinfo.cc`` CMake
flags. Here features report what the JAX/XLA installation provides.
"""

import collections


class Feature(collections.namedtuple('Feature', ['name', 'enabled'])):
    def __repr__(self):
        return f'{"✔" if self.enabled else "✖"} {self.name}'


class Features(dict):
    """Map of runtime feature → enabled (reference runtime.py:Features)."""

    def __init__(self):
        import jax
        platforms = {d.platform for d in jax.devices()}
        feats = {
            'TPU': any(p != 'cpu' for p in platforms),
            'CPU': True,
            'CUDA': False,
            'CUDNN': False,
            'NCCL': False,
            'XLA': True,
            'PALLAS': True,
            'BF16': True,
            'INT64_TENSOR_SIZE': True,
            'DIST_KVSTORE': True,
            'SIGNAL_HANDLER': True,
            'OPENCV': _has('cv2'),
            'MKLDNN': False,
            'TVM_OP': False,
        }
        super().__init__({k: Feature(k, v) for k, v in feats.items()})

    def is_enabled(self, name):
        feat = self.get(name.upper())
        return bool(feat and feat.enabled)


def _has(mod):
    import importlib.util
    return importlib.util.find_spec(mod) is not None


def feature_list():
    return list(Features().values())
